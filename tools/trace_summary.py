"""Summarize a chrome trace file from the command line.

Usage::

    python tools/trace_summary.py path/to/trace.json[.gz] \
        [--top 15] [--json]

Works on anything the `paddle_tpu.observability.trace` layer writes —
a `Tracer.save()` capture, a GET /trace response body, a flight-recorder
dump, or a `merge_fleet_trace` fleet timeline — and on any other
chrome-trace-event file (object or bare-array format).

Reports:

* **top spans by self-time** — per span name: count, total wall,
  self-time (total minus nested child spans on the same pid/tid track),
  mean/max duration.  Self-time is what makes "where did the time go"
  answerable when `step` contains `executor.run` contains nothing;
* **per-signature serving latency breakdown** — reassembles the
  per-request async timelines (`ph:"b"/"e"`, cat `serving`) the
  InferenceServer emits, joins them to the batch signature via the
  `batch.pad` span's `trace_ids` arg, and prints per signature: request
  count, mean/p50/p99 end-to-end latency, and the mean per-phase split
  (queue / pad+dispatch / xla_compute / slice);
* the dump reason + straggler verdict when the file is a flight-recorder
  dump or a merged fleet trace.

Exit code: 1 when the file is missing or not a loadable chrome trace,
0 otherwise.  `--json` prints one machine-readable object instead of
the tables.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from collections import defaultdict

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _pct(sorted_vals, q):
    if not sorted_vals:
        return None
    i = min(int(q * (len(sorted_vals) - 1) + 0.5), len(sorted_vals) - 1)
    return sorted_vals[i]


def span_stats(events):
    """Per-name span statistics from ph:"X" events, with self-time.

    Self-time: a span's duration minus the durations of spans nested
    strictly inside it on the same (pid, tid) track — computed with a
    sweep stack per track (events sorted by start, ties broken longest
    first so parents enter before their children).
    """
    by_track = defaultdict(list)
    for ev in events:
        if ev.get("ph") == "X" and "ts" in ev:
            by_track[(ev.get("pid"), ev.get("tid"))].append(ev)
    stats = {}

    def acct(name):
        return stats.setdefault(name, {
            "count": 0, "total_us": 0, "self_us": 0, "max_us": 0})

    for track in by_track.values():
        track.sort(key=lambda e: (e["ts"], -e.get("dur", 0)))
        stack = []   # (name, end_ts, child_us accumulator index)
        for ev in track:
            name, ts = ev.get("name", "?"), ev["ts"]
            dur = int(ev.get("dur", 0))
            while stack and ts >= stack[-1][1]:
                stack.pop()
            if stack:
                stack[-1][2]["child_us"] = \
                    stack[-1][2].get("child_us", 0) + dur
            s = acct(name)
            s["count"] += 1
            s["total_us"] += dur
            s["max_us"] = max(s["max_us"], dur)
            holder = {}
            stack.append((name, ts + dur, holder))
            # defer self-time: subtract children once the span closes —
            # but the sweep pops lazily, so bill at push via holder
            s.setdefault("_holders", []).append((holder, dur))
    for s in stats.values():
        self_us = 0
        for holder, dur in s.pop("_holders", []):
            self_us += max(dur - holder.get("child_us", 0), 0)
        s["self_us"] = self_us
        s["mean_us"] = s["total_us"] / s["count"] if s["count"] else 0
    return stats


def serving_breakdown(events):
    """Per-signature request latency from the serving async timelines."""
    # request phases: {trace_id: {phase: us}}; overall span from the
    # "request" b/e pair
    begins = {}
    phases = defaultdict(dict)
    for ev in events:
        if ev.get("cat") != "serving" or ev.get("ph") not in ("b", "e"):
            continue
        key = (ev.get("id"), ev.get("name"))
        if ev["ph"] == "b":
            begins[key] = ev.get("ts", 0)
        else:
            t0 = begins.pop(key, None)
            if t0 is not None:
                phases[ev.get("id")][ev.get("name")] = \
                    ev.get("ts", 0) - t0
    # trace_id -> signature from batch.pad / batch.dispatch span args
    sig_of = {}
    for ev in events:
        if ev.get("ph") == "X" and ev.get("name", "").startswith("batch."):
            args = ev.get("args") or {}
            sig = args.get("signature")
            for tid in args.get("trace_ids") or ():
                if sig:
                    sig_of[tid] = sig
    groups = defaultdict(list)
    for tid, ph in phases.items():
        if "request" in ph:
            groups[sig_of.get(tid, "(unknown)")].append(ph)
    out = {}
    for sig, reqs in sorted(groups.items()):
        lats = sorted(r["request"] for r in reqs)
        ent = {
            "requests": len(reqs),
            "mean_ms": round(sum(lats) / len(lats) / 1e3, 3),
            "p50_ms": round(_pct(lats, 0.50) / 1e3, 3),
            "p99_ms": round(_pct(lats, 0.99) / 1e3, 3),
            "phases_mean_ms": {},
        }
        for phase in ("queue", "pad+dispatch", "xla_compute", "slice"):
            vals = [r[phase] for r in reqs if phase in r]
            if vals:
                ent["phases_mean_ms"][phase] = \
                    round(sum(vals) / len(vals) / 1e3, 3)
        out[sig] = ent
    return out


def summarize(path, top=15):
    from paddle_tpu.observability.trace import load_trace

    events, metadata = load_trace(path)
    stats = span_stats(events)
    ranked = sorted(stats.items(), key=lambda kv: -kv[1]["self_us"])[:top]
    return {
        "path": os.fspath(path),
        "events": len(events),
        "metadata": {k: metadata[k] for k in
                     ("reason", "stragglers", "ranks", "merged_shards",
                      "pid") if k in metadata},
        "top_spans_by_self_time": [
            dict(name=name, count=s["count"],
                 total_ms=round(s["total_us"] / 1e3, 3),
                 self_ms=round(s["self_us"] / 1e3, 3),
                 mean_ms=round(s["mean_us"] / 1e3, 3),
                 max_ms=round(s["max_us"] / 1e3, 3))
            for name, s in ranked],
        "serving": serving_breakdown(events),
    }


def _print_tables(summary):
    print("%s: %d events" % (summary["path"], summary["events"]))
    md = summary["metadata"]
    if md.get("reason"):
        print("flight-recorder dump; reason: %s" % md["reason"])
    strag = (md.get("stragglers") or {}).get("ranks")
    if strag:
        print("stragglers: ranks %s (ratios %s)"
              % (strag, md["stragglers"]["ratios"]))
    rows = summary["top_spans_by_self_time"]
    if rows:
        print("\ntop spans by self-time:")
        print("  %-28s %8s %12s %12s %10s %10s"
              % ("name", "count", "self ms", "total ms",
                 "mean ms", "max ms"))
        for r in rows:
            print("  %-28s %8d %12.3f %12.3f %10.3f %10.3f"
                  % (r["name"][:28], r["count"], r["self_ms"],
                     r["total_ms"], r["mean_ms"], r["max_ms"]))
    if summary["serving"]:
        print("\nserving latency by signature:")
        for sig, ent in summary["serving"].items():
            print("  %s: n=%d mean=%.3fms p50=%.3fms p99=%.3fms"
                  % (sig, ent["requests"], ent["mean_ms"],
                     ent["p50_ms"], ent["p99_ms"]))
            if ent["phases_mean_ms"]:
                print("    phases (mean ms): %s" % " ".join(
                    "%s=%.3f" % kv
                    for kv in ent["phases_mean_ms"].items()))


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="trace_summary",
        description="summarize a chrome trace / flight-recorder dump")
    ap.add_argument("trace", help="trace file (.json or .json.gz)")
    ap.add_argument("--top", type=int, default=15,
                    help="span rows to show (default 15)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="print one machine-readable JSON object")
    args = ap.parse_args(argv)
    try:
        summary = summarize(args.trace, top=args.top)
    except (OSError, ValueError) as e:
        print("trace_summary: cannot read %r: %s" % (args.trace, e),
              file=sys.stderr)
        return 1
    if args.as_json:
        print(json.dumps(summary, indent=2, sort_keys=True))
    else:
        _print_tables(summary)
    return 0


if __name__ == "__main__":
    sys.exit(main())
