"""Static cost report for a serialized program from the command line.

Usage::

    python tools/program_cost.py path/to/__model__.json \
        [--dynamic-dim 8] [--peak-flops 1.97e14] [--hbm-bw 8.19e11] \
        [--ici-bw 4.5e10] [--host-bw 1.6e10] [--mesh dp=8] [--top 10] \
        [--json] [--no-ops] [--budget-ms 5.0]

Runs the `paddle_tpu.analysis.perf` static cost model (FLOPs / bytes /
roofline time per op on a parameterized chip) over the program and
prints per-op-type rollups, or the full machine-readable report with
--json.  Also accepts an inference-model DIRECTORY (as written by
save_inference_model).

``--mesh`` (e.g. ``dp=8`` or ``dp=4,tp=2``) supplies the collective
group size for explicit c_* collective ops that carry no ``nranks``
attr, and ``--ici-bw`` the interconnect bytes/s they are priced
against — communication enters the roofline exactly like FLOPs and HBM
(`analysis.comm` ring factors; totals gain ``comm_bytes``).

Exit code: 1 when the model is unreadable or when --budget-ms is given
and the estimated whole-program time exceeds it; 0 otherwise.

JSON schema (``schema_version`` 1, pinned for CI consumers)::

    {
      "schema_version": 1,
      "model": "<path>",
      "chip": {"name": str, "peak_flops": float, "hbm_bw": float,
               "ici_bw": float | null, "host_bw": float | null},
      "dynamic_dim": int,
      "totals": {"flops", "transcendentals", "bytes", "comm_bytes",
                 "host_bytes", "time_s", "arithmetic_intensity",
                 "op_count"},
      "by_op_type": [{"op_type", "count", "flops", "bytes",
                      "comm_bytes", "host_bytes", "time_s"}],
      "ops": [{"block_idx", "op_idx", "op_type", "flops",
               "transcendentals", "bytes", "comm_bytes", "host_bytes",
               "time_s", "bound", "provenance"}], # omitted with --no-ops
      "budget_ms": float | null,
      "within_budget": bool | null
    }
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(_HERE)
sys.path.insert(0, REPO)
sys.path.insert(1, _HERE)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="program_cost",
        description="static FLOPs/bytes/roofline-time report for a "
                    "serialized program")
    ap.add_argument("model", help="program JSON file or inference model dir")
    ap.add_argument("--dynamic-dim", type=int, default=None,
                    help="extent substituted for -1 dims (default 8)")
    ap.add_argument("--peak-flops", type=float, default=None,
                    help="chip peak FLOP/s (default: env/platform table, "
                         "v5e fallback)")
    ap.add_argument("--hbm-bw", type=float, default=None,
                    help="chip HBM bytes/s (same resolution order)")
    ap.add_argument("--ici-bw", type=float, default=None,
                    help="chip ICI bytes/s for collective pricing "
                         "(same resolution order, v5e fallback)")
    ap.add_argument("--host-bw", type=float, default=None,
                    help="host link bytes/s for distributed-embedding "
                         "exchange pricing (same resolution order, "
                         "v5e fallback)")
    ap.add_argument("--mesh", default=None,
                    help="mesh axes 'dp=8' or 'dp=4,tp=2': the product "
                         "is the collective group size for c_* ops "
                         "without an nranks attr")
    ap.add_argument("--top", type=int, default=10,
                    help="rows in the per-op-type table (text mode)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the full report as JSON")
    ap.add_argument("--no-ops", action="store_true",
                    help="omit the per-op array from --json output")
    ap.add_argument("--budget-ms", type=float, default=None,
                    help="exit 1 when the estimated program time "
                         "exceeds this many milliseconds")
    args = ap.parse_args(argv)

    from program_lint import _load

    from paddle_tpu.analysis import perf

    try:
        program, _feed, _fetch = _load(args.model)
    except SystemExit:
        raise
    except Exception as e:
        print("error: cannot load %r: %s" % (args.model, e),
              file=sys.stderr)
        return 1

    chip = perf.ChipSpec.detect(peak_flops=args.peak_flops,
                                hbm_bw=args.hbm_bw, ici_bw=args.ici_bw,
                                host_bw=args.host_bw)
    mesh_size = None
    if args.mesh:
        try:
            parts = [p for p in args.mesh.split(",") if p.strip()]
            if not parts or any("=" not in p for p in parts):
                raise ValueError(args.mesh)
            mesh_size = 1
            for p in parts:
                size = int(p.split("=", 1)[1])
                if size < 1:
                    # dp=0 (an unset $N) must not silently price every
                    # collective as free
                    raise ValueError(p)
                mesh_size *= size
        except (ValueError, IndexError):
            print("error: --mesh wants 'axis=N[,axis=N...]' with N >= 1, "
                  "got %r" % args.mesh, file=sys.stderr)
            return 1
    kw = {}
    if args.dynamic_dim is not None:
        kw["dynamic_dim"] = args.dynamic_dim
    report = perf.program_cost(program, chip=chip, mesh_size=mesh_size,
                               **kw)

    over_budget = (args.budget_ms is not None
                   and report.total_time_s * 1e3 > args.budget_ms)

    if args.as_json:
        d = report.to_dict(include_ops=not args.no_ops)
        d["model"] = args.model
        d["budget_ms"] = args.budget_ms
        d["within_budget"] = (None if args.budget_ms is None
                              else not over_budget)
        print(json.dumps(d, indent=2))
    else:
        print(report.format(top=args.top))
        if args.budget_ms is not None:
            print("budget: est %.3f ms %s %.3f ms budget" % (
                report.total_time_s * 1e3,
                "EXCEEDS" if over_budget else "within", args.budget_ms))

    return 1 if over_budget else 0


if __name__ == "__main__":
    sys.exit(main())
