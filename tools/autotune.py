"""Operator CLI for the measured autotuner (`paddle_tpu.tune`).

Usage::

    # tune a serialized program's pass pipeline (search report to stdout)
    python tools/autotune.py path/to/__model__.json --fetch out.tmp_0 \
        [--json] [--budget-s 120] [--k 5] [--warmup 1] \
        [--cache-dir DIR] [--no-cache] [--dynamic-dim 8]

    # pre-tune a serving model's batch-bucket ladder from an observed
    # traffic sample (request batch sizes), then deploy with the winner
    python tools/autotune.py model_dir --ladder-traffic 1,1,3,7,1,2 \
        [--max-batch 32] [--json]

    # tune flash-attention block sizes for one shape
    python tools/autotune.py --flash 8,12,512,64 [--causal] \
        [--layout BHSD] [--flash-backward] [--json]

The report lists every candidate with its terminal status — ``timed``
(est + measured + attributed compile time), ``pruned`` (statically
rejected, never compiled), ``excluded`` (broken by a pass, offender
named), ``skipped_budget`` — plus the winner vs the measured default.
``--json`` emits `SearchReport.to_dict()` (schema_version 1).

Exit code: 1 when the model is unreadable or the search produced no
winner; 0 otherwise.  A cache hit prints the stored winner and compiles
nothing — delete the entry (path printed) to force a re-search.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(_HERE)
sys.path.insert(0, REPO)
sys.path.insert(1, _HERE)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="autotune",
        description="measured autotuner: search pass pipelines, serving "
                    "bucket ladders, or flash-attention block sizes")
    ap.add_argument("model", nargs="?", default=None,
                    help="program JSON file or inference model dir "
                         "(omit with --flash)")
    ap.add_argument("--fetch", default="",
                    help="comma-separated fetch var names (overrides the "
                         "model dir's recorded fetches)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the full SearchReport as JSON")
    ap.add_argument("--budget-s", type=float, default=None,
                    help="bound the compile-and-time phase (baseline "
                         "always runs; the rest becomes skipped_budget)")
    ap.add_argument("--k", type=int, default=5,
                    help="timed repetitions per candidate (median)")
    ap.add_argument("--warmup", type=int, default=1,
                    help="warmup calls per candidate (compile happens "
                         "here and is attributed separately)")
    ap.add_argument("--dynamic-dim", type=int, default=None,
                    help="extent substituted for -1 dims (default 8)")
    ap.add_argument("--pipelines", default=None,
                    help="semicolon-separated candidate pipelines, each "
                         "a comma-separated list of registered pass "
                         "names (an empty entry is the baseline); "
                         "replaces the default registry-enumerated "
                         "space, e.g. ';batch_norm_act_fuse'")
    ap.add_argument("--cache-dir", default=None,
                    help="tuning cache directory (default: the "
                         "persistent compile-cache dir)")
    ap.add_argument("--no-cache", action="store_true",
                    help="search even when a cached winner exists, and "
                         "do not store the result")
    # ladder mode
    ap.add_argument("--ladder-traffic", default=None,
                    help="comma-separated observed request batch sizes; "
                         "switches to bucket-ladder tuning against the "
                         "model dir's Predictor")
    ap.add_argument("--max-batch", type=int, default=32,
                    help="ladder mode: max coalesced batch")
    # flash mode
    ap.add_argument("--flash", default=None,
                    help="B,H,S,D (BHSD) or B,S,H,D (BSHD) q shape; "
                         "switches to flash block-size tuning")
    ap.add_argument("--kv-len", type=int, default=None,
                    help="flash mode: key/value length (default: S)")
    ap.add_argument("--causal", action="store_true",
                    help="flash mode: causal masking")
    ap.add_argument("--layout", default="BHSD", choices=("BHSD", "BSHD"),
                    help="flash mode: q/k/v layout")
    ap.add_argument("--flash-backward", action="store_true",
                    help="flash mode: time forward+backward")
    args = ap.parse_args(argv)

    from paddle_tpu import tune

    kw = dict(use_cache=not args.no_cache, cache_dir=args.cache_dir,
              warmup=args.warmup, k=args.k)

    if args.flash:
        try:
            shape = tuple(int(s) for s in args.flash.split(","))
            if len(shape) != 4:
                raise ValueError("need 4 dims")
        except ValueError as e:
            print("error: --flash expects B,H,S,D: %s" % e,
                  file=sys.stderr)
            return 1
        report = tune.search_flash_blocks(
            shape, kv_len=args.kv_len, causal=args.causal,
            layout=args.layout, include_backward=args.flash_backward,
            **kw)
        return _emit(report, args)

    if args.model is None:
        print("error: a model path is required (or use --flash)",
              file=sys.stderr)
        return 1

    if args.ladder_traffic is not None:
        try:
            traffic = [int(s) for s in args.ladder_traffic.split(",") if s]
        except ValueError:
            print("error: --ladder-traffic expects comma-separated ints",
                  file=sys.stderr)
            return 1
        try:
            from paddle_tpu.inference import AnalysisConfig, Predictor

            pred = Predictor(AnalysisConfig(args.model))
        except Exception as e:
            print("error: cannot load predictor from %r: %s"
                  % (args.model, e), file=sys.stderr)
            return 1
        example = _example_feed(pred)
        report = tune.search_bucket_ladder(
            pred, example, traffic, max_batch=args.max_batch, **kw)
        return _emit(report, args)

    from program_lint import _load

    try:
        program, _feeds, fetches = _load(args.model)
    except SystemExit:
        raise
    except Exception as e:
        print("error: cannot load %r: %s" % (args.model, e),
              file=sys.stderr)
        return 1
    if args.fetch:
        fetches = [s for s in args.fetch.split(",") if s]
    if not fetches:
        print("error: no fetch names (pass --fetch or use a model dir "
              "with recorded fetches)", file=sys.stderr)
        return 1
    skw = dict(kw)
    if args.dynamic_dim is not None:
        skw["dynamic_dim"] = args.dynamic_dim
    if args.pipelines is not None:
        pipes = [[n for n in cand.split(",") if n]
                 for cand in args.pipelines.split(";")]
        skw["space"] = tune.SearchSpace(pipelines=pipes, donate=(True,),
                                        sharding=False)
    report = tune.search(program, fetches, budget_s=args.budget_s, **skw)
    return _emit(report, args)


def _example_feed(pred):
    """Zero batch-1 example from the predictor's recorded feed shapes."""
    import numpy as np

    from paddle_tpu.analysis.perf import DEFAULT_DYNAMIC_DIM

    block = pred._program.global_block
    feed = {}
    for n in pred.get_input_names():
        v = block._find_var_recursive(n)
        shape = [1] + [DEFAULT_DYNAMIC_DIM if s == -1 else int(s)
                       for s in (v.shape or ())[1:]]
        from paddle_tpu.fluid.core import dtypes as dtypes_mod

        feed[n] = np.zeros(tuple(shape),
                           np.dtype(dtypes_mod.to_jnp(v.dtype)))
    return feed


def _emit(report, args):
    if args.as_json:
        print(json.dumps(report.to_dict(), indent=2))
    else:
        print(report.format())
    return 0 if report.winner is not None else 1


if __name__ == "__main__":
    sys.exit(main())
