#!/usr/bin/env python
"""Run a configurable kill/reshape/restart elasticity drill.

The scriptable entry point CI and operators share: train a small
deterministic DP job across real OS processes, SIGKILL a rank mid-epoch
(and/or inject filesystem faults or stale heartbeats), let the elastic
controller drain/fence/reshape/relaunch over a world-size schedule, and
exit non-zero unless recovery provably converged — post-resume
trajectory identical to an uninterrupted control run at the new
topology, every sample consumed exactly once, loss down.

Examples::

    # lose a rank of 4 at global step 12, recover on 3
    python tools/elastic_drill.py --workspace /tmp/drill \
        --world-sizes 4,3 --kill-rank 1 --kill-step 12

    # grow 2 -> 4 after a stale-heartbeat hang instead of a kill
    python tools/elastic_drill.py --workspace /tmp/drill \
        --world-sizes 2,4 --no-kill \
        --fault '{"kind": "stall_heartbeat", "rank": 0, "step": 9}'

    # flaky-FS resilience: every rank retries transient EIO on commit
    python tools/elastic_drill.py --workspace /tmp/drill \
        --world-sizes 2,2 --kill-rank 1 --kill-step 9 \
        --retry-attempts 3 \
        --fault '{"kind": "fs_error", "rank": 0, "op": "mv", "times": 2}'
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None):
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--workspace", required=True,
                   help="shared drill directory (created if missing)")
    p.add_argument("--world-sizes", default="3,2",
                   help="comma schedule: generation g runs at the g-th "
                        "size (last repeats)")
    p.add_argument("--kill-rank", type=int, default=1)
    p.add_argument("--kill-step", type=int, default=12,
                   help="global step (epoch-permutation position // "
                        "global batch) the rank dies at")
    p.add_argument("--no-kill", action="store_true",
                   help="no SIGKILL event (drive failures via --fault)")
    p.add_argument("--fault", action="append", default=[],
                   help="extra FaultPlan event as JSON (repeatable)")
    p.add_argument("--epochs", type=int, default=None)
    p.add_argument("--global-batch", type=int, default=None)
    p.add_argument("--n-samples", type=int, default=None)
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--save-every", type=int, default=None,
                   help="mid-epoch checkpoint cadence in local batches")
    p.add_argument("--retry-attempts", type=int, default=None,
                   help="CheckpointSaver transient-I/O retries per rank")
    p.add_argument("--no-control", action="store_true",
                   help="skip the control-run trajectory comparison")
    p.add_argument("--json", action="store_true",
                   help="print the full report as JSON")
    args = p.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from paddle_tpu.distributed.elastic.drill import run_drill

    config = {}
    for key, val in (("epochs", args.epochs),
                     ("global_batch", args.global_batch),
                     ("n_samples", args.n_samples),
                     ("seed", args.seed),
                     ("save_every", args.save_every),
                     ("retry_attempts", args.retry_attempts)):
        if val is not None:
            config[key] = val
    report = run_drill(
        args.workspace,
        world_sizes=[int(w) for w in args.world_sizes.split(",")],
        kill_rank=None if args.no_kill else args.kill_rank,
        kill_step=args.kill_step,
        config=config,
        fault_events=[json.loads(f) for f in args.fault],
        control=not args.no_control,
    )
    if args.json:
        print(json.dumps(report, indent=2, default=str))
    else:
        for name, ok in sorted(report["checks"].items()):
            print("%-28s %s" % (name, ok))
        print("generations: %s" % json.dumps(
            [(h["generation"], h["world_size"], h["event"]["kind"])
             for h in report["controller"]["history"]]))
        print("PASSED" if report["passed"] else "FAILED")
    return 0 if report["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
