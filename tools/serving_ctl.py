"""Operator CLI for a running `paddle_tpu.serving` front tier.

Speaks the /admin plane of `serving.serve_http`::

    python tools/serving_ctl.py --endpoint http://host:port COMMAND ...

    list                                  # versions, states, pointers
    stats                                 # router stats()
    deploy  -v V --model-dir DIR [--replicas N] [--kind thread|process]
            [--warmup-inputs '{"x": [[0.0, ...]]}']
    promote -v V [--keep-old]             # atomic cutover (+standby)
    rollback                              # back to the kept previous
    canary  -v V --percent P              # deterministic split (0 clears)
    shadow  [-v V | --off]                # mirror traffic (never returned)
    retire  -v V                          # drain + close replicas
    drain   -v V                          # alias of retire
    slo                                   # GET /slo; rc 1 on active alerts
    trace   [--trace-id ID] [--out FILE]  # GET /trace (merged timeline)

Exit codes: 0 on success; **1 on a refused transition** (HTTP 409 —
promote a non-ready version, retire the stable one, rollback with no
standby, a deploy whose verify gate rejected the model) or any other
HTTP/connection error.  ``--json`` prints the raw response object for
scripting; the default output is a short human line.
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.error
import urllib.request


def _call(endpoint, path, body=None, timeout=120.0):
    """(status_code, parsed_json).  Connection failures -> (None, err)."""
    url = endpoint.rstrip("/") + path
    if body is None:
        req = urllib.request.Request(url)
    else:
        req = urllib.request.Request(
            url, data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        try:
            payload = json.loads(e.read())
        except Exception:
            payload = {"error": str(e)}
        return e.code, payload
    except Exception as e:
        return None, {"error": "%s: %s" % (type(e).__name__, e)}


def _emit(args, code, payload):
    ok = code is not None and 200 <= code < 300
    if args.json:
        print(json.dumps({"status": code, "ok": ok, "response": payload},
                         indent=2, sort_keys=True))
    elif not ok:
        refused = isinstance(payload, dict) and payload.get("refused")
        print("%s (HTTP %s): %s"
              % ("refused" if refused else "error", code,
                 payload.get("error", payload)
                 if isinstance(payload, dict) else payload),
              file=sys.stderr)
    return 0 if ok else 1


def cmd_list(args):
    code, payload = _call(args.endpoint, "/admin/models")
    rc = _emit(args, code, payload)
    if rc == 0 and not args.json:
        print("stable:   %s" % payload.get("stable"))
        if payload.get("canary"):
            print("canary:   %s @ %.1f%%" % (
                payload["canary"]["version"], payload["canary"]["percent"]))
        if payload.get("shadow"):
            print("shadow:   %s" % payload["shadow"])
        if payload.get("previous_stable"):
            print("previous: %s" % payload["previous_stable"])
        for mv in payload.get("versions", []):
            print("  %-16s %-9s replicas %d/%d  requests %d%s" % (
                mv["version"], mv["state"], mv["replicas_alive"],
                mv["replicas"], mv["requests"],
                ("  [%s]" % mv["error"]) if mv.get("error") else ""))
    return rc


def cmd_stats(args):
    code, payload = _call(args.endpoint, "/stats")
    if not args.json and code == 200:
        print(json.dumps(payload, indent=2, sort_keys=True))
        return 0
    return _emit(args, code, payload)


def cmd_deploy(args):
    body = {"version": args.version, "model_dir": args.model_dir,
            "replicas": args.replicas, "kind": args.kind}
    if args.warmup_inputs:
        body["warmup_inputs"] = json.loads(args.warmup_inputs)
    code, payload = _call(args.endpoint, "/admin/deploy", body)
    rc = _emit(args, code, payload)
    if rc == 0 and not args.json:
        print("deployed %s: state %s, %d replica(s)"
              % (payload["version"], payload["state"], payload["replicas"]))
    return rc


def cmd_promote(args):
    code, payload = _call(args.endpoint, "/admin/promote",
                          {"version": args.version,
                           "keep_old": args.keep_old})
    rc = _emit(args, code, payload)
    if rc == 0 and not args.json:
        print("promoted %s (state %s)" % (payload["version"],
                                          payload["state"]))
    return rc


def cmd_rollback(args):
    code, payload = _call(args.endpoint, "/admin/rollback", {})
    rc = _emit(args, code, payload)
    if rc == 0 and not args.json:
        print("rolled back to %s" % payload["version"])
    return rc


def cmd_canary(args):
    code, payload = _call(args.endpoint, "/admin/canary",
                          {"version": args.version,
                           "percent": args.percent})
    rc = _emit(args, code, payload)
    if rc == 0 and not args.json:
        print("canary: %s" % (payload.get("canary") or "off"))
    return rc


def cmd_shadow(args):
    version = None if args.off else args.version
    if version is None and not args.off:
        print("shadow needs -v VERSION or --off", file=sys.stderr)
        return 2
    code, payload = _call(args.endpoint, "/admin/shadow",
                          {"version": version})
    rc = _emit(args, code, payload)
    if rc == 0 and not args.json:
        print("shadow: %s" % (payload.get("shadow") or "off"))
    return rc


def cmd_slo(args):
    code, payload = _call(args.endpoint, "/slo")
    rc = _emit(args, code, payload)
    if code == 200 and not args.json:
        print("slo %s: window %d, goodput %s" % (
            payload.get("slo"), payload.get("window", 0),
            ("%.4f" % payload["goodput"])
            if payload.get("goodput") is not None else "n/a"))
        for obj in payload.get("objectives", []):
            v = obj.get("value")
            print("  %-12s %-12s %s  (<= %g)  %s" % (
                obj["name"], obj["metric"],
                "n/a" if v is None else "%.4g" % v,
                obj["threshold"], "ok" if obj["ok"] else "ALERT"))
        for w, r in sorted((payload.get("burn_rate") or {}).items()):
            print("  burn %-8s %.3f" % (w, r))
    # active alerts fail the invocation even on HTTP 200: `serving_ctl
    # slo` is the CI/cron probe, rc!=0 IS the page
    if rc == 0 and payload.get("alerts"):
        if not args.json:
            print("active alerts: %s" % ", ".join(payload["alerts"]),
                  file=sys.stderr)
        return 1
    return rc


def cmd_trace(args):
    path = "/trace"
    if args.trace_id:
        path += "?trace_id=%s" % args.trace_id
    code, payload = _call(args.endpoint, path)
    if code == 200 and args.out:
        with open(args.out, "w") as f:
            json.dump(payload, f)
        if not args.json:
            print("wrote %d events to %s"
                  % (len(payload.get("traceEvents", [])), args.out))
            return 0
    rc = _emit(args, code, payload)
    if rc == 0 and not args.json and not args.out:
        evs = payload.get("traceEvents", [])
        md = payload.get("metadata", {})
        print("%d events%s%s" % (
            len(evs),
            ", trace_id %s" % md["trace_id"]
            if md.get("trace_id") else "",
            ", anchor-aligned" if md.get("aligned") else ""))
    return rc


def cmd_retire(args):
    code, payload = _call(args.endpoint, "/admin/retire",
                          {"version": args.version})
    rc = _emit(args, code, payload)
    if rc == 0 and not args.json:
        print("retired %s" % payload["version"])
    return rc


def build_parser():
    p = argparse.ArgumentParser(
        prog="serving_ctl",
        description="Operate a running paddle_tpu.serving front tier.")
    p.add_argument("--endpoint", default="http://127.0.0.1:8080",
                   help="front tier base URL (default %(default)s)")
    p.add_argument("--json", action="store_true",
                   help="print raw JSON responses (scripting)")
    sub = p.add_subparsers(dest="command", required=True)

    sub.add_parser("list").set_defaults(fn=cmd_list)
    sub.add_parser("stats").set_defaults(fn=cmd_stats)

    d = sub.add_parser("deploy")
    d.add_argument("-v", "--version", required=True)
    d.add_argument("--model-dir", required=True)
    d.add_argument("--replicas", type=int, default=1)
    d.add_argument("--kind", choices=("thread", "process"),
                   default="thread")
    d.add_argument("--warmup-inputs", default=None,
                   help='JSON example inputs, e.g. \'{"x": [[0.0, 0.0]]}\''
                        " — warms the full bucket ladder")
    d.set_defaults(fn=cmd_deploy)

    pr = sub.add_parser("promote")
    pr.add_argument("-v", "--version", required=True)
    pr.add_argument("--keep-old", action="store_true",
                    help="keep the old stable on warm standby (rollback "
                         "target) instead of retiring it")
    pr.set_defaults(fn=cmd_promote)

    sub.add_parser("rollback").set_defaults(fn=cmd_rollback)

    c = sub.add_parser("canary")
    c.add_argument("-v", "--version", required=True)
    c.add_argument("--percent", type=float, required=True)
    c.set_defaults(fn=cmd_canary)

    s = sub.add_parser("shadow")
    s.add_argument("-v", "--version", default=None)
    s.add_argument("--off", action="store_true")
    s.set_defaults(fn=cmd_shadow)

    for alias in ("retire", "drain"):   # drain = retire (drain-then-close)
        r = sub.add_parser(alias)
        r.add_argument("-v", "--version", required=True)
        r.set_defaults(fn=cmd_retire)

    sl = sub.add_parser(
        "slo", help="GET /slo — rc 1 on active alerts (the cron probe)")
    sl.set_defaults(fn=cmd_slo)

    t = sub.add_parser(
        "trace", help="GET /trace — merged fleet timeline (rc 1 while "
                      "tracing is disabled: HTTP 409)")
    t.add_argument("--trace-id", default=None,
                   help="filter to one request's timeline")
    t.add_argument("--out", default=None, metavar="FILE",
                   help="write the chrome-trace JSON here (open in "
                        "Perfetto) instead of printing a summary")
    t.set_defaults(fn=cmd_trace)
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
