"""Operator CLI for a `paddle_tpu.rl.FeedbackLoop` behind its control
plane (`rl.serve_rl_http`)::

    python tools/rl_ctl.py --endpoint http://host:8093 COMMAND

    status                   # healthz + readyz + running flag, one line
    stats                    # loop stats(): round, reward history tail,
                             # baseline, rollout ledger, push records
    start [--rounds N]       # kick off a run (rc 1 + message if one is
                             # already active: the plane answers 409)
    stop                     # request a graceful stop (finishes the
                             # in-flight round, then drains)

Exit code 0 on success; 1 when the plane refuses (409 start-while-
running), the loop is unreachable, or it reports not-ready.  ``--json``
prints machine-readable envelopes for scripting — ``status --json``
emits ``{"healthy":..., "ready":..., "running":..., "error":...}`` so a
promotion pipeline can gate on a single call.
"""

from __future__ import annotations

import argparse
import http.client
import json
import sys
import urllib.parse


def _request(endpoint, method, path, body=None, timeout=30.0):
    u = urllib.parse.urlparse(endpoint)
    conn = http.client.HTTPConnection(u.hostname, u.port or 80,
                                      timeout=timeout)
    try:
        payload = None if body is None else json.dumps(body)
        headers = {"Content-Type": "application/json"} if payload else {}
        conn.request(method, path, payload, headers)
        resp = conn.getresponse()
        raw = resp.read()
        try:
            return resp.status, json.loads(raw)
        except ValueError:
            return resp.status, {"raw": raw.decode("utf-8", "replace")}
    finally:
        conn.close()


def cmd_status(args):
    h_code, _ = _request(args.endpoint, "GET", "/healthz",
                         timeout=args.timeout)
    r_code, r_body = _request(args.endpoint, "GET", "/readyz",
                              timeout=args.timeout)
    s_code, s_body = _request(args.endpoint, "GET", "/stats",
                              timeout=args.timeout)
    out = {
        "healthy": h_code == 200,
        "ready": r_code == 200,
        "running": bool(s_body.get("running")) if s_code == 200 else None,
        "round": s_body.get("round"),
        "pushes": s_body.get("pushes"),
        "error": s_body.get("error") or r_body.get("reason"),
    }
    ok = out["healthy"] and out["ready"] and not out["error"]
    if args.json:
        print(json.dumps(out))
    else:
        print("rl loop: %s, %s, %s (round %s, %s pushes)%s" % (
            "healthy" if out["healthy"] else "UNHEALTHY",
            "ready" if out["ready"] else "NOT READY",
            "running" if out["running"] else "idle",
            out["round"], out["pushes"],
            " — error: %s" % out["error"] if out["error"] else ""))
    return 0 if ok else 1


def cmd_stats(args):
    code, payload = _request(args.endpoint, "GET", "/stats",
                             timeout=args.timeout)
    print(json.dumps(payload) if args.json
          else "stats (HTTP %s): %s" % (code, json.dumps(payload)))
    return 0 if code == 200 else 1


def cmd_start(args):
    body = {}
    if args.rounds is not None:
        body["rounds"] = args.rounds
    code, payload = _request(args.endpoint, "POST", "/start", body,
                             timeout=args.timeout)
    if args.json:
        payload = dict(payload)
        payload["http"] = code
        print(json.dumps(payload))
    elif code == 200:
        print("started (rounds=%s)" % payload.get("rounds"))
    elif code == 409:
        print("refused: %s" % payload.get("error"), file=sys.stderr)
    else:
        print("HTTP %d: %s" % (code, json.dumps(payload)),
              file=sys.stderr)
    return 0 if code == 200 else 1


def cmd_stop(args):
    code, payload = _request(args.endpoint, "POST", "/stop",
                             timeout=args.timeout)
    if args.json:
        payload = dict(payload)
        payload["http"] = code
        print(json.dumps(payload))
    else:
        print("stop requested (was %s)" %
              ("running" if payload.get("stopping") else "idle"))
    return 0 if code == 200 else 1


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--endpoint", default="http://127.0.0.1:8093")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--timeout", type=float, default=30.0)
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("status")
    sub.add_parser("stats")
    st = sub.add_parser("start")
    st.add_argument("--rounds", type=int, default=None)
    sub.add_parser("stop")
    args = ap.parse_args(argv)
    try:
        return {"status": cmd_status, "stats": cmd_stats,
                "start": cmd_start, "stop": cmd_stop}[args.cmd](args)
    except Exception as e:
        msg = {"error": "%s: %s" % (type(e).__name__, e)}
        print(json.dumps(msg) if args.json else msg["error"],
              file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
