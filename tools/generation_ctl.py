"""Operator CLI + smoke driver for a `paddle_tpu.generation` engine
behind an HTTP front (`serving.serve_generation_http`, or
`serving.serve_http(generation_fleet=...)`)::

    python tools/generation_ctl.py --endpoint http://host:port COMMAND

    stats                                # fleet stats() (slot occupancy)
    kv                                   # condensed paged-KV gauges per
                                         # replica: pool fill, prefix hit
                                         # rate, speculative acceptance
    tp                                   # model-parallel gauges: shard
                                         # groups (membership, queue
                                         # depth, KV-transfer bytes) and
                                         # per-replica TP degree
    generate --prompt "1,2,3" [--max-new N] [--temperature T]
             [--top-k K] [--top-p P] [--seed S] [--no-stream]
    smoke    [--requests N] [--max-new M] [--concurrency C]
             [--prompt-vocab V]

``smoke`` is the CI/ops liveness drill: it streams N prompts through a
LIVE engine (C at a time) and asserts every stream is COMPLETE and
ORDERED — token indices 0..k-1 contiguous with no duplicate, no gap, a
terminal done record, and the token count consistent with it.  A
``restart`` record (replica died mid-generation; the fleet re-queued
the request once) legally resets the expected index to 0.  Exit code 0
only when every stream checks out; any dropped, duplicated, or
out-of-order token (or transport error) is rc 1 with the offending
request named — wire this against a canary front before promoting a
new engine build.

``--json`` prints machine-readable envelopes for scripting.
"""

from __future__ import annotations

import argparse
import http.client
import json
import sys
import threading
import urllib.parse


def _conn(endpoint, timeout):
    u = urllib.parse.urlparse(endpoint)
    return http.client.HTTPConnection(u.hostname, u.port or 80,
                                      timeout=timeout)


def _get_json(endpoint, path, timeout=30.0):
    conn = _conn(endpoint, timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())
    finally:
        conn.close()


def stream_generate(endpoint, body, timeout=60.0):
    """POST /generate with stream=true; yields parsed ndjson records."""
    conn = _conn(endpoint, timeout)
    try:
        payload = dict(body)
        payload["stream"] = True
        conn.request("POST", "/generate", json.dumps(payload),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        if resp.status != 200:
            raise RuntimeError(
                "HTTP %d: %s" % (resp.status, resp.read()[:300]))
        while True:
            line = resp.readline()
            if not line:
                return
            line = line.strip()
            if line:
                yield json.loads(line)
    finally:
        conn.close()


def check_stream(records):
    """The smoke invariant: contiguous 0..k-1 indices (restart resets),
    exactly one terminal done record, count consistent.  Returns
    (ok, reason, tokens)."""
    expected = 0
    tokens = []
    done = None
    for rec in records:
        if "error" in rec:
            return False, "stream error: %s" % rec["error"], tokens
        if rec.get("event") == "restart":
            expected = 0
            tokens = []
            continue
        if rec.get("done"):
            if done is not None:
                return False, "duplicate done record", tokens
            done = rec
            continue
        if done is not None:
            return False, "token after done record", tokens
        idx = rec.get("index")
        if idx != expected:
            kind = "duplicated" if (idx is not None and idx < expected) \
                else "dropped"
            return False, ("%s token: expected index %d, got %r"
                           % (kind, expected, idx)), tokens
        tokens.append(rec["token"])
        expected += 1
    if done is None:
        return False, "stream ended without a done record", tokens
    if done.get("n_tokens") != len(tokens):
        return False, ("done record says %r tokens, stream carried %d"
                       % (done.get("n_tokens"), len(tokens))), tokens
    return True, "ok", tokens


def cmd_stats(args):
    code, payload = _get_json(args.endpoint, "/stats")
    print(json.dumps(payload) if args.json
          else "stats (HTTP %s): %s" % (code, json.dumps(payload)))
    return 0 if code == 200 else 1


def cmd_kv(args):
    """Condensed per-replica paged-KV view off /stats — the pool-sizing
    signals: block-pool fill, prefix-cache hit rate, speculative
    acceptance, preemption count."""
    code, payload = _get_json(args.endpoint, "/stats")
    if code != 200:
        print(json.dumps(payload), file=sys.stderr)
        return 1
    rows = []
    for r in payload.get("replicas", []):
        cache = r.get("kv_cache") or {}
        row = {"replica": r.get("replica_id"),
               "paged": cache.get("paged", False),
               "preempted": r.get("preempted", 0)}
        if row["paged"]:
            row.update(blocks_used=cache.get("blocks_used"),
                       blocks_free=cache.get("blocks_free"),
                       block_size=cache.get("block_size"),
                       kv_dtype=cache.get("kv_dtype"))
        if "prefix_cache" in r:
            row["prefix_hit_rate"] = r["prefix_cache"].get("hit_rate")
            row["prefix_hit_tokens"] = r["prefix_cache"].get("hit_tokens")
        if "speculative" in r:
            row["acceptance_rate"] = \
                r["speculative"].get("acceptance_rate")
        rows.append(row)
    if args.json:
        print(json.dumps({"replicas": rows}))
    else:
        for row in rows:
            print(" ".join("%s=%s" % kv for kv in row.items()))
    return 0


def cmd_tp(args):
    """Model-parallel view off /stats (`paddle_tpu.tp_serving`): one
    row per shard group — membership (prefill/decode engine names),
    per-group decode queue depth and headroom, cumulative KV-transfer
    bytes, and the decode worker's prefill-executable pin — plus the
    TP degree of any tensor-parallel replica in a plain fleet."""
    code, payload = _get_json(args.endpoint, "/stats")
    if code != 200:
        print(json.dumps(payload), file=sys.stderr)
        return 1
    groups = []
    for g in payload.get("shard_groups", []):
        row = {"group": g.get("group_id"),
               "members": g.get("members"),
               "roles": g.get("roles"),
               "handoffs": g.get("handoffs"),
               "kv_transfer_bytes": g.get("kv_transfer_bytes"),
               "queue_depth": g.get("queue_depth"),
               "free_decode_slots": g.get("free_decode_slots"),
               "headroom": g.get("headroom"),
               "prefill_executables": g.get("prefill_executables")}
        if "tp" in g:
            row["tp"] = g["tp"].get("degree")
        groups.append(row)
    replicas = []
    for r in payload.get("replicas", []):
        if "tp" in r:
            replicas.append({"replica": r.get("replica_id"),
                             "tp": r["tp"].get("degree"),
                             "kv_heads_per_shard":
                                 r["tp"].get("kv_heads_per_shard")})
    out = {"shard_groups": groups, "tp_replicas": replicas,
           "kv_transfer_bytes": payload.get("kv_transfer_bytes", 0)}
    if args.json:
        print(json.dumps(out))
    elif not groups and not replicas:
        print("no shard groups or tensor-parallel replicas at %s"
              % args.endpoint)
    else:
        for row in groups:
            print("group %s: %s" % (
                row["group"],
                " ".join("%s=%s" % kv for kv in row.items()
                         if kv[0] != "group")))
        for row in replicas:
            print("replica %s: tp=%s kv_heads_per_shard=%s"
                  % (row["replica"], row["tp"],
                     row["kv_heads_per_shard"]))
    return 0


def cmd_generate(args):
    body = {
        "prompt": [int(t) for t in args.prompt.split(",")],
        "max_new_tokens": args.max_new,
        "temperature": args.temperature,
        "top_k": args.top_k, "top_p": args.top_p, "seed": args.seed,
    }
    if args.no_stream:
        conn = _conn(args.endpoint, args.timeout)
        try:
            body["stream"] = False
            conn.request("POST", "/generate", json.dumps(body),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            payload = json.loads(resp.read())
            print(json.dumps(payload) if args.json else
                  "tokens: %s (%s)" % (payload.get("tokens"),
                                       payload.get("reason")))
            return 0 if resp.status == 200 else 1
        finally:
            conn.close()
    records = list(stream_generate(args.endpoint, body,
                                   timeout=args.timeout))
    ok, reason, tokens = check_stream(records)
    if args.json:
        print(json.dumps({"ok": ok, "reason": reason,
                          "tokens": tokens}))
    else:
        print("tokens: %s (%s)" % (tokens, reason))
    return 0 if ok else 1


def cmd_smoke(args):
    """See module docstring."""
    results = [None] * args.requests
    sem = threading.Semaphore(args.concurrency)

    def one(i):
        with sem:
            body = {
                "prompt": [1 + (i + j) % args.prompt_vocab
                           for j in range(2 + i % 6)],
                "max_new_tokens": args.max_new,
                "temperature": 0.0, "seed": i,
                "request_id": "smoke-%d" % i,
            }
            try:
                records = list(stream_generate(
                    args.endpoint, body, timeout=args.timeout))
                results[i] = check_stream(records)
            except Exception as e:
                results[i] = (False, "%s: %s" % (type(e).__name__, e), [])

    threads = [threading.Thread(target=one, args=(i,))
               for i in range(args.requests)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    failures = [(i, "no result (worker died)" if r is None else r[1])
                for i, r in enumerate(results)
                if r is None or not r[0]]
    n_tokens = sum(len(r[2]) for r in results if r)
    out = {"requests": args.requests, "tokens": n_tokens,
           "failures": [{"request": i, "reason": why}
                        for i, why in failures],
           "ok": not failures}
    print(json.dumps(out) if args.json else
          ("smoke: %d requests, %d tokens, %s"
           % (args.requests, n_tokens,
              "ALL STREAMS COMPLETE AND ORDERED" if not failures else
              "%d FAILED: %s" % (len(failures), failures))))
    return 0 if not failures else 1


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--endpoint", default="http://127.0.0.1:8090")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--timeout", type=float, default=60.0)
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("stats")
    sub.add_parser("kv")
    sub.add_parser("tp")
    g = sub.add_parser("generate")
    g.add_argument("--prompt", required=True,
                   help="comma-separated token ids")
    g.add_argument("--max-new", type=int, default=16)
    g.add_argument("--temperature", type=float, default=0.0)
    g.add_argument("--top-k", type=int, default=0)
    g.add_argument("--top-p", type=float, default=1.0)
    g.add_argument("--seed", type=int, default=0)
    g.add_argument("--no-stream", action="store_true")
    s = sub.add_parser("smoke")
    s.add_argument("--requests", type=int, default=8)
    s.add_argument("--max-new", type=int, default=8)
    s.add_argument("--concurrency", type=int, default=4)
    s.add_argument("--prompt-vocab", type=int, default=100)
    args = ap.parse_args(argv)
    try:
        return {"stats": cmd_stats, "kv": cmd_kv, "tp": cmd_tp,
                "generate": cmd_generate,
                "smoke": cmd_smoke}[args.cmd](args)
    except Exception as e:
        msg = {"error": "%s: %s" % (type(e).__name__, e)}
        print(json.dumps(msg) if args.json else msg["error"],
              file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
