"""Static thread-safety lint over paddle_tpu/ sources.

Usage::

    python tools/concurrency_lint.py [paths...] [--json] [--strict] \
        [--rules lock-order-inversion,...] [--list-rules]

Runs the "concurrency"-category lint rules
(`paddle_tpu.analysis.concurrency`): nested `with lock:` orders are
extracted into a lock-order graph (AB/BA inversions report both sites),
blocking-call patterns under a held lock are flagged, and non-reentrant
locks acquired inside `signal.signal` handlers are flagged — all from
source alone, nothing is executed.

`paths` are files or directories (default: the paddle_tpu/ package).
Findings waived in place with ``# concurrency-ok[<code>]: <reason>``
are reported at INFO severity and never affect the exit code.

Exit code 1 when any error-severity finding exists, or with --strict
when any non-waived (non-INFO) finding exists; 0 otherwise — the tier-1
gate runs ``--strict`` over the shipped tree.

JSON output (``--json``) is an object pinned by ``schema_version``
(currently 1), matching tools/program_lint.py::

    {
      "schema_version": 1,
      "diagnostics": [{severity, code, message, block_idx, op_idx,
                       op_type, var_names, provenance, pass_name}],
      "summary": {"errors": int, "warnings": int, "waived": int,
                  "total": int}
    }
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SCHEMA_VERSION = 1


def _collect_files(paths):
    files = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, _dirs, names in os.walk(p):
                for n in sorted(names):
                    if n.endswith(".py"):
                        files.append(os.path.join(dirpath, n))
        else:
            files.append(p)
    return files


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="concurrency_lint",
        description="static lock-order / blocking-under-lock / "
                    "signal-safety lint over Python sources")
    ap.add_argument("paths", nargs="*",
                    help="files or directories (default: paddle_tpu/)")
    ap.add_argument("--rules", default="",
                    help="comma-separated rule subset (default: all "
                         "concurrency-category rules)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the registered concurrency rules and exit")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit diagnostics as a schema-versioned JSON "
                         "object")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on ANY non-waived finding, not just "
                         "errors")
    args = ap.parse_args(argv)

    from paddle_tpu.analysis import concurrency  # registers the rules
    from paddle_tpu.analysis.diagnostics import INFO
    from paddle_tpu.analysis.lint import lint_rules

    if args.list_rules:
        for name in lint_rules(category="concurrency"):
            print(name)
        return 0

    if args.paths:
        files = _collect_files(args.paths)
    else:
        files = _collect_files([os.path.join(REPO, "paddle_tpu")])

    rules = [s for s in args.rules.split(",") if s] if args.rules else None
    diags = concurrency.lint_sources(files=files, rules=rules)

    waived = [d for d in diags if d.severity == INFO]
    if args.as_json:
        print(json.dumps({
            "schema_version": SCHEMA_VERSION,
            "diagnostics": [d.to_dict() for d in diags.sorted()],
            "summary": {"errors": len(diags.errors()),
                        "warnings": len(diags.warnings()),
                        "waived": len(waived),
                        "total": len(diags)},
        }, indent=2))
    else:
        print(diags.format())

    rc = 0
    if diags.has_errors:
        rc = 1
    elif args.strict and any(d.severity != INFO for d in diags):
        rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
